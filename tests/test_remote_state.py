"""Multi-host control plane: remote state-store replica over HTTP
(SURVEY §5 distributed comm backend; the ZK-spectator analogue)."""

import time

import numpy as np
import pytest

from pinot_tpu.controller.controller import Controller
from pinot_tpu.controller.state import ClusterStateStore
from pinot_tpu.transport.state_service import (
    RemoteClusterStateStore,
    StateStoreApi,
)


@pytest.fixture
def authority():
    store = ClusterStateStore()
    api = StateStoreApi(store, port=0)
    api.start()
    yield store, f"http://localhost:{api.port}"
    api.stop()


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


class TestReplica:
    def test_read_your_writes_and_replication(self, authority):
        store, url = authority
        remote = RemoteClusterStateStore(url)
        try:
            remote.set("a/b", {"x": 1})
            assert remote.get("a/b") == {"x": 1}       # own write, local
            assert store.get("a/b") == {"x": 1}        # authority has it
            store.set("a/c", [1, 2])                   # other-writer path
            assert _wait(lambda: remote.get("a/c") == [1, 2])
        finally:
            remote.close()

    def test_watch_fires_on_remote_mutation(self, authority):
        store, url = authority
        remote = RemoteClusterStateStore(url)
        seen = []
        remote.watch("tables/", lambda p, v: seen.append((p, v)))
        try:
            store.set("tables/t1", {"n": 1})
            assert _wait(lambda: ("tables/t1", {"n": 1}) in seen)
        finally:
            remote.close()

    def test_update_is_atomic_across_clients(self, authority):
        store, url = authority
        a = RemoteClusterStateStore(url)
        b = RemoteClusterStateStore(url)
        try:
            import threading

            def bump(client, n):
                for _ in range(n):
                    client.update("counter", lambda v: (v or 0) + 1,
                                  default=0)

            ts = [threading.Thread(target=bump, args=(c, 25))
                  for c in (a, b)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert store.get("counter") == 50
        finally:
            a.close()
            b.close()

    def test_delete_replicates(self, authority):
        store, url = authority
        remote = RemoteClusterStateStore(url)
        try:
            store.set("gone/x", 1)
            assert _wait(lambda: remote.get("gone/x") == 1)
            remote.delete("gone/x")
            assert store.get("gone/x") is None
        finally:
            remote.close()

    def test_full_resync_after_log_overflow(self, authority):
        store, url = authority
        remote = RemoteClusterStateStore(url, poll_interval_s=10)  # stalled
        try:
            for i in range(ClusterStateStore._LOG_CAP + 50):
                store.set("k", i)
            # replica is far behind the log tail: next sync snapshots
            remote._sync_once()
            assert remote.get("k") == ClusterStateStore._LOG_CAP + 49
        finally:
            remote.close()


class TestMultiHostCluster:
    def test_remote_roles_end_to_end(self, authority, tmp_path):
        """Controller local; server + broker on 'another host': control
        plane over the HTTP replica, data plane over gRPC."""
        from pinot_tpu.broker.broker import BrokerRequestHandler
        from pinot_tpu.segment import SegmentBuilder
        from pinot_tpu.server.server import ServerInstance
        from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
        from pinot_tpu.spi.table import TableConfig
        from pinot_tpu.transport.grpc_transport import (
            GrpcQueryServer,
            GrpcServerStub,
        )

        store, url = authority
        controller = Controller(store)

        schema = Schema("rs", [
            FieldSpec("k", DataType.STRING),
            FieldSpec("v", DataType.LONG, FieldType.METRIC)])
        controller.add_schema(schema)
        controller.add_table(TableConfig(table_name="rs"))

        # --- the "remote host" ------------------------------------------
        server_store = RemoteClusterStateStore(url)
        broker_store = RemoteClusterStateStore(url)
        server = ServerInstance("remote_server_0", server_store,
                                segment_dir=str(tmp_path / "srv"))
        server.start()
        grpc_srv = GrpcQueryServer(server, port=0)
        grpc_srv.start()
        broker = BrokerRequestHandler(broker_store)
        broker.register_server(
            "remote_server_0", GrpcServerStub(f"localhost:{grpc_srv.port}"))
        try:
            rng = np.random.default_rng(5)
            frame = {"k": ["a", "b"] * 600,
                     "v": rng.integers(0, 50, 1200).tolist()}
            sm = SegmentBuilder(schema, "rs_0").build(frame, str(tmp_path))
            controller.add_segment("rs_OFFLINE", sm,
                                   str(tmp_path / "rs_0"))
            # the remote server sees the assignment via its replica watch,
            # downloads, serves; EV flows back through its replica writes
            assert _wait(lambda: "rs_0" in server.hosted_segments(
                "rs_OFFLINE"), timeout=10)
            # ...and the broker's own replica must observe the EV too
            assert _wait(lambda: "rs_0" in broker_store.get_external_view(
                "rs_OFFLINE"), timeout=10)
            resp = broker.handle_sql(
                "SELECT k, sum(v) FROM rs GROUP BY k ORDER BY k")
            expect_a = sum(v for k, v in zip(frame["k"], frame["v"])
                           if k == "a")
            assert resp.result_table.rows[0] == ["a", expect_a]
        finally:
            server.shutdown()
            grpc_srv.stop()
            server_store.close()
            broker_store.close()


class TestAuthorityRestart:
    def test_replica_survives_authority_restart(self, tmp_path):
        """The authority process restarts (same snapshot file, new port):
        replicas pointed at the new endpoint resync and writes flow again —
        the ZK-reconnect analogue for deployment rolls."""
        snap = str(tmp_path / "state.json")
        store1 = ClusterStateStore(snapshot_path=snap)
        api1 = StateStoreApi(store1, port=0)
        api1.start()
        remote = RemoteClusterStateStore(f"http://localhost:{api1.port}")
        try:
            try:
                remote.set("tables/t1", {"n": 1})
                assert store1.get("tables/t1") == {"n": 1}
            finally:
                api1.stop()

            # polls fail while the authority is down; reads stay local
            assert remote.get("tables/t1") == {"n": 1}

            # restart from the snapshot on a NEW port
            store2 = ClusterStateStore(snapshot_path=snap)
            assert store2.get("tables/t1") == {"n": 1}  # durable
            api2 = StateStoreApi(store2, port=0)
            api2.start()
            try:
                remote.reconnect(f"http://localhost:{api2.port}")
                store2.set("tables/t2", {"n": 2})
                assert _wait(lambda: remote.get("tables/t2") == {"n": 2})
                remote.set("tables/t3", {"n": 3})
                assert store2.get("tables/t3") == {"n": 3}
            finally:
                api2.stop()
        finally:
            remote.close()
