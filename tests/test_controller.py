"""Controller layer: state store semantics, assignment strategies, the
segment-completion FSM (exactly-one-committer), LLC lifecycle, retention
(ref: PinotHelixResourceManager / SegmentCompletionManager /
PinotLLCRealtimeSegmentManager / RetentionManager)."""

import threading

import pytest

from pinot_tpu.controller import (
    BalancedSegmentAssignment,
    CONSUMING,
    ClusterStateStore,
    Controller,
    FsmState,
    InstanceInfo,
    ONLINE,
    ReplicaGroupSegmentAssignment,
    SegmentCompletionManager,
    SegmentZKMetadata,
    compute_target_assignment,
    rebalance_steps,
)
from pinot_tpu.ingestion import (
    CompletionResponse,
    ConsumerState,
    MemoryStream,
    RealtimeSegmentDataManager,
    StreamOffset,
)
from pinot_tpu.segment.metadata import SegmentMetadata
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
)


def make_schema(name="events"):
    return Schema(name, [
        FieldSpec("user", DataType.STRING),
        FieldSpec("value", DataType.LONG, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ])


def offline_table(name="events"):
    return TableConfig(name, TableType.OFFLINE,
                       validation_config=SegmentsValidationConfig(
                           time_column_name="ts", replication=2))


def seg_md(name, table="events_OFFLINE", **kw):
    return SegmentZKMetadata(segment_name=name, table_name=table, **kw)


# --------------------------------------------------------------------------
# state store
# --------------------------------------------------------------------------

class TestStateStore:
    def test_crud_and_versioning(self):
        s = ClusterStateStore()
        v1 = s.set("a/b", {"x": 1})
        v2 = s.set("a/c", 2)
        assert v2 > v1
        assert s.get("a/b") == {"x": 1}
        assert s.children("a") == ["a/b", "a/c"]
        s.delete("a/b")
        assert s.get("a/b") is None

    def test_watches_fire_in_order(self):
        s = ClusterStateStore()
        seen = []
        s.watch("tables/", lambda p, v: seen.append((p, v)))
        s.set("tables/t1", 1)
        s.set("other/x", 2)
        s.set("tables/t2", 3)
        assert seen == [("tables/t1", 1), ("tables/t2", 3)]

    def test_snapshot_persistence(self, tmp_path):
        path = str(tmp_path / "state.json")
        s = ClusterStateStore(snapshot_path=path)
        s.add_schema(make_schema())
        s.set_segment_metadata(seg_md("s1", total_docs=5))
        reloaded = ClusterStateStore(snapshot_path=path)
        assert reloaded.get_schema("events").schema_name == "events"
        assert reloaded.get_segment_metadata("events_OFFLINE", "s1").total_docs == 5
        assert reloaded.version == s.version

    def test_external_view_rollup(self):
        s = ClusterStateStore()
        s.report_instance_state("t", "seg1", "server_0", ONLINE)
        s.report_instance_state("t", "seg1", "server_1", ONLINE)
        assert s.get_external_view("t") == {
            "seg1": {"server_0": "ONLINE", "server_1": "ONLINE"}}
        s.report_instance_state("t", "seg1", "server_0", "OFFLINE")
        assert s.get_external_view("t") == {"seg1": {"server_1": "ONLINE"}}


# --------------------------------------------------------------------------
# assignment + rebalance
# --------------------------------------------------------------------------

class TestAssignment:
    def test_balanced_spreads_load(self):
        a = BalancedSegmentAssignment()
        current = {}
        servers = ["s0", "s1", "s2"]
        for i in range(6):
            chosen = a.assign(f"seg{i}", current, servers, 1)
            current[f"seg{i}"] = {c: ONLINE for c in chosen}
        counts = {}
        for m in current.values():
            for inst in m:
                counts[inst] = counts.get(inst, 0) + 1
        assert counts == {"s0": 2, "s1": 2, "s2": 2}

    def test_replication_capped_by_instances(self):
        a = BalancedSegmentAssignment()
        assert len(a.assign("seg", {}, ["s0", "s1"], 3)) == 2

    def test_replica_group(self):
        a = ReplicaGroupSegmentAssignment(num_replica_groups=2)
        chosen = a.assign("seg0", {}, ["s0", "s1", "s2", "s3"], 2)
        # one from each group {s0,s2} and {s1,s3}
        assert len(chosen) == 2
        assert (chosen[0] in ("s0", "s2")) != (chosen[0] in ("s1", "s3"))

    def test_rebalance_make_before_break(self):
        current = {"seg0": {"s0": ONLINE}, "seg1": {"s0": ONLINE}}
        target = compute_target_assignment(current, ["s0", "s1"], 1)
        steps = rebalance_steps(current, target)
        assert steps[-1] == target
        # every intermediate step keeps each segment served
        for step in steps:
            for seg in current:
                assert len(step.get(seg, {})) >= 1


# --------------------------------------------------------------------------
# completion FSM
# --------------------------------------------------------------------------

class TestCompletionFsm:
    def test_single_replica_commits(self):
        m = SegmentCompletionManager(hold_window_s=0.0)
        r = m.segment_consumed("seg", "s0", StreamOffset(100))
        assert r.response is CompletionResponse.COMMIT
        assert m.segment_commit_start("seg", "s0", StreamOffset(100)).response \
            is CompletionResponse.COMMIT
        assert m.segment_commit_end("seg", "s0", StreamOffset(100), "loc",
                                    None).response is CompletionResponse.COMMIT
        assert m.fsm_state("seg") is FsmState.COMMITTED

    def test_highest_offset_wins_and_laggard_catches_up(self):
        m = SegmentCompletionManager(num_replicas_provider=lambda s: 2,
                                     hold_window_s=10.0)
        r0 = m.segment_consumed("seg", "s0", StreamOffset(90))
        assert r0.response is CompletionResponse.HOLD  # waiting for s1
        r1 = m.segment_consumed("seg", "s1", StreamOffset(100))
        r0b = m.segment_consumed("seg", "s0", StreamOffset(90))
        # s1 has the higher offset: s1 commits, s0 catches up to 100
        assert {r1.response, r0b.response} == {CompletionResponse.COMMIT,
                                               CompletionResponse.CATCHUP}
        if r0b.response is CompletionResponse.CATCHUP:
            assert r0b.target_offset == StreamOffset(100)

    def test_exactly_one_committer_under_concurrency(self):
        m = SegmentCompletionManager(num_replicas_provider=lambda s: 4,
                                     hold_window_s=0.0)
        replies = {}
        barrier = threading.Barrier(4)

        def replica(i):
            barrier.wait()
            replies[i] = m.segment_consumed("seg", f"s{i}", StreamOffset(100))

        threads = [threading.Thread(target=replica, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # re-poll until all have a decision
        for i in range(4):
            if replies[i].response is CompletionResponse.HOLD:
                replies[i] = m.segment_consumed("seg", f"s{i}", StreamOffset(100))
        committers = [i for i, r in replies.items()
                      if r.response is CompletionResponse.COMMIT]
        assert len(committers) == 1

    def test_non_winner_keep_after_commit_same_offset(self):
        m = SegmentCompletionManager(num_replicas_provider=lambda s: 2,
                                     hold_window_s=0.0)
        m.segment_consumed("seg", "s0", StreamOffset(100))
        m.segment_commit_start("seg", "s0", StreamOffset(100))
        m.segment_commit_end("seg", "s0", StreamOffset(100), "loc", None)
        same = m.segment_consumed("seg", "s1", StreamOffset(100))
        assert same.response is CompletionResponse.KEEP
        diverged = m.segment_consumed("seg", "s2", StreamOffset(90))
        assert diverged.response is CompletionResponse.DISCARD

    def test_dead_replica_during_holding_not_elected(self):
        m = SegmentCompletionManager(num_replicas_provider=lambda s: 2,
                                     hold_window_s=10.0)
        assert m.segment_consumed("seg", "s1", StreamOffset(100)).response \
            is CompletionResponse.HOLD
        m.segment_stopped_consuming("seg", "s1", "crash")
        # s0 must not lose to the dead s1's stale offset
        r = m.segment_consumed("seg", "s0", StreamOffset(90))
        for _ in range(50):
            if r.response is not CompletionResponse.HOLD:
                break
            import time as _t
            _t.sleep(0.01)
            r = m.segment_consumed("seg", "s0", StreamOffset(90))
        # window still open with num_replicas=2; force by second report
        r = m.segment_consumed("seg", "s0", StreamOffset(95))
        m2 = SegmentCompletionManager(num_replicas_provider=lambda s: 2,
                                      hold_window_s=0.0)
        m2.segment_consumed("seg", "s1", StreamOffset(100))
        m2.segment_stopped_consuming("seg", "s1", "crash")
        r2 = m2.segment_consumed("seg", "s0", StreamOffset(90))
        assert r2.response is CompletionResponse.COMMIT

    def test_committer_death_reopens_election(self):
        m = SegmentCompletionManager(num_replicas_provider=lambda s: 2,
                                     hold_window_s=0.0)
        r0 = m.segment_consumed("seg", "s0", StreamOffset(100))
        assert r0.response is CompletionResponse.COMMIT
        m.segment_stopped_consuming("seg", "s0", "crash")
        r1 = m.segment_consumed("seg", "s1", StreamOffset(100))
        assert r1.response is CompletionResponse.COMMIT

    def test_committer_timeout_reelects_without_stopped_notification(self):
        # committer crashes WITHOUT segment_stopped_consuming: after the
        # max-commit window the election re-opens and a live peer commits
        # (ref: SegmentCompletionManager MAX_COMMIT_TIME_FOR_ALL_SEGMENTS)
        m = SegmentCompletionManager(num_replicas_provider=lambda s: 2,
                                     hold_window_s=0.0,
                                     max_commit_time_s=0.0)
        r0 = m.segment_consumed("seg", "s0", StreamOffset(100))
        assert r0.response is CompletionResponse.COMMIT
        # s0 dies silently; s1 keeps reporting at the winner offset
        r1 = m.segment_consumed("seg", "s1", StreamOffset(100))
        assert r1.response is CompletionResponse.COMMIT
        assert m._fsms["seg"].committer == "s1"


# --------------------------------------------------------------------------
# controller end-to-end (LLC lifecycle, retention, rebalance)
# --------------------------------------------------------------------------

class TestController:
    def _controller_with_servers(self, n=2):
        c = Controller(llc_seed="20260729T0000Z")
        for i in range(n):
            c.register_instance(InstanceInfo(f"server_{i}", "SERVER"))
        return c

    def test_add_offline_table_and_segments(self):
        c = self._controller_with_servers(3)
        c.add_schema(make_schema())
        c.add_table(offline_table())
        md = SegmentMetadata("events_0", "events", make_schema(), 100, 1024,
                             min_time=0, max_time=10)
        c.add_segment("events_OFFLINE", md, "file:///tmp/events_0")
        ideal = c.store.get_ideal_state("events_OFFLINE")
        assert len(ideal["events_0"]) == 2  # replication
        zk = c.store.get_segment_metadata("events_OFFLINE", "events_0")
        assert zk.status == ONLINE and zk.total_docs == 100

    def test_realtime_table_setup_creates_consuming(self):
        MemoryStream.create("ctrl_topic", 2)
        c = self._controller_with_servers(2)
        c.add_schema(make_schema())
        tc = TableConfig(
            "events", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=StreamIngestionConfig(
                stream_type="memory", topic="ctrl_topic",
                segment_flush_threshold_rows=100))
        c.add_table(tc)
        mds = c.store.segment_metadata_list("events_REALTIME")
        assert len(mds) == 2
        assert all(m.status == CONSUMING for m in mds)
        assert {m.partition for m in mds} == {0, 1}
        MemoryStream.delete("ctrl_topic")

    def test_realtime_commit_through_fsm(self, tmp_path):
        """Full loop: consumer negotiates with the controller FSM; commit
        flips ONLINE and creates the next CONSUMING sequence."""
        MemoryStream.create("fsm_topic", 1)
        c = self._controller_with_servers(1)
        c.add_schema(make_schema())
        tc = TableConfig(
            "events", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=StreamIngestionConfig(
                stream_type="memory", topic="fsm_topic",
                segment_flush_threshold_rows=50))
        c.add_table(tc)
        seg_name = c.store.segment_metadata_list("events_REALTIME")[0].segment_name

        for i in range(60):
            MemoryStream.get("fsm_topic").produce(
                {"user": f"u{i % 3}", "value": i, "ts": 1000 + i}, partition=0)

        mgr = RealtimeSegmentDataManager(
            seg_name, tc, make_schema(), partition=0,
            start_offset=StreamOffset(0), protocol=c.completion,
            instance_id="server_0", output_dir=str(tmp_path))
        res = mgr.consume_until_committed()
        assert res.state is ConsumerState.COMMITTED
        assert res.rows_indexed == 50

        mds = {m.segment_name: m for m in
               c.store.segment_metadata_list("events_REALTIME")}
        committed = mds[seg_name]
        assert committed.status == ONLINE
        assert committed.end_offset == "50"
        assert committed.total_docs == 50
        nxt = [m for m in mds.values() if m.status == CONSUMING]
        assert len(nxt) == 1 and nxt[0].sequence == 1
        assert nxt[0].start_offset == "50"
        MemoryStream.delete("fsm_topic")

    def test_retention_deletes_expired(self):
        c = self._controller_with_servers(1)
        c.add_schema(make_schema())
        cfg = TableConfig("events", TableType.OFFLINE,
                          validation_config=SegmentsValidationConfig(
                              time_column_name="ts", time_type="MILLISECONDS",
                              retention_time_unit="DAYS",
                              retention_time_value=7))
        c.add_table(cfg)
        day_ms = 86_400_000
        now = 100 * day_ms
        fresh = SegmentMetadata("fresh", "events", make_schema(), 1, 1024,
                                min_time=now - day_ms, max_time=now - day_ms)
        stale = SegmentMetadata("stale", "events", make_schema(), 1, 1024,
                                min_time=now - 30 * day_ms,
                                max_time=now - 30 * day_ms)
        c.add_segment("events_OFFLINE", fresh, "loc")
        c.add_segment("events_OFFLINE", stale, "loc")
        deleted = c.run_retention_manager(now_ms=now)
        assert deleted == ["stale"]
        assert c.store.segment_names("events_OFFLINE") == ["fresh"]

    def test_realtime_validation_repairs_dead_consumption(self):
        MemoryStream.create("repair_topic", 2)
        c = self._controller_with_servers(1)
        c.add_schema(make_schema())
        tc = TableConfig(
            "events", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=StreamIngestionConfig(
                stream_type="memory", topic="repair_topic"))
        c.add_table(tc)
        # kill partition 1's consuming segment (simulates ERROR/deletion)
        victim = [m for m in c.store.segment_metadata_list("events_REALTIME")
                  if m.partition == 1][0]
        c.store.delete_segment("events_REALTIME", victim.segment_name)
        created = c.run_realtime_validation()
        assert len(created) == 1
        md = c.store.get_segment_metadata("events_REALTIME", created[0])
        assert md.partition == 1 and md.status == CONSUMING
        MemoryStream.delete("repair_topic")

    def test_rebalance_after_adding_server(self):
        c = self._controller_with_servers(1)
        c.add_schema(make_schema())
        cfg = TableConfig("events", TableType.OFFLINE,
                          validation_config=SegmentsValidationConfig(
                              time_column_name="ts", replication=1))
        c.add_table(cfg)
        for i in range(4):
            md = SegmentMetadata(f"events_{i}", "events", make_schema(), 10, 1024)
            c.add_segment("events_OFFLINE", md, "loc")
        before = c.store.get_ideal_state("events_OFFLINE")
        assert all(list(m) == ["server_0"] for m in before.values())

        c.register_instance(InstanceInfo("server_1", "SERVER"))
        c.rebalance_table("events_OFFLINE", convergence_timeout_s=0.1)
        after = c.store.get_ideal_state("events_OFFLINE")
        per_server = {}
        for m in after.values():
            for inst in m:
                per_server[inst] = per_server.get(inst, 0) + 1
        assert per_server == {"server_0": 2, "server_1": 2}
