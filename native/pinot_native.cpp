// Native host runtime for pinot_tpu.
//
// The TPU-native equivalent of the reference's JVM-off-heap/JNI layer
// (ref: pinot-segment-spi memory/PinotDataBuffer.java:54 backed by
// xerial.larray JNI mmap, and the fixed-bit packing hot loops in
// io/util/PinotDataBitSet.java:25 / FixedBitSVForwardIndexWriter):
// C ABI exported for ctypes binding — no Python in the hot loops.
//
// Components:
//   - fixed-bit pack/unpack of dictId arrays (the dominant storage format;
//     unpack feeds int32 HBM-staging buffers directly)
//   - mmap buffer manager with refcounts (the PinotDataBuffer role: segment
//     files mapped once, shared across readers, unmapped on last release)
//   - CRC32 over files (creation.meta CRC, V1Constants.java:56)
//   - delta + varint encode/decode for sorted doc-id lists (the inverted
//     index posting-list form; RoaringBitmap-equivalent storage)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// fixed-bit packing (ref: PinotDataBitSet unaligned bit extraction)
// ---------------------------------------------------------------------------

// packed size in bytes for n values at `bits` bits each (64-bit aligned tail)
int64_t pn_packed_size(int64_t n, int32_t bits) {
    int64_t total_bits = n * (int64_t)bits;
    return ((total_bits + 63) / 64) * 8;
}

// pack int32 values (all < 2^bits) into dst; returns bytes written, -1 on error
int64_t pn_bitpack_i32(const int32_t* src, int64_t n, int32_t bits,
                       uint8_t* dst, int64_t dst_cap) {
    if (bits <= 0 || bits > 32) return -1;
    int64_t need = pn_packed_size(n, bits);
    if (dst_cap < need) return -1;
    std::memset(dst, 0, (size_t)need);
    uint64_t* words = (uint64_t*)dst;
    for (int64_t i = 0; i < n; i++) {
        uint64_t v = (uint32_t)src[i];
        int64_t bit_pos = i * (int64_t)bits;
        int64_t w = bit_pos >> 6;
        int32_t off = (int32_t)(bit_pos & 63);
        words[w] |= v << off;
        if (off + bits > 64) {
            words[w + 1] |= v >> (64 - off);
        }
    }
    return need;
}

// unpack n values of `bits` bits into int32 dst
int64_t pn_bitunpack_i32(const uint8_t* src, int64_t src_len, int64_t n,
                         int32_t bits, int32_t* dst) {
    if (bits <= 0 || bits > 32) return -1;
    if (src_len < pn_packed_size(n, bits)) return -1;
    const uint64_t* words = (const uint64_t*)src;
    uint64_t mask = (bits == 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (int64_t i = 0; i < n; i++) {
        int64_t bit_pos = i * (int64_t)bits;
        int64_t w = bit_pos >> 6;
        int32_t off = (int32_t)(bit_pos & 63);
        uint64_t v = words[w] >> off;
        if (off + bits > 64) {
            v |= words[w + 1] << (64 - off);
        }
        dst[i] = (int32_t)(v & mask);
    }
    return n;
}

// ---------------------------------------------------------------------------
// mmap buffer manager (ref: PinotDataBuffer mapFile/refcount protocol —
// the same acquire/release hazard protocol the HBM staging cache uses)
// ---------------------------------------------------------------------------

struct MappedBuffer {
    void* addr;
    int64_t size;
    int32_t refcount;
};

static std::map<int64_t, MappedBuffer> g_buffers;
static std::mutex g_buffers_mu;
static int64_t g_next_handle = 1;

// map a file read-only; returns handle > 0, or <= 0 on error
int64_t pn_mmap_open(const char* path) {
    int fd = open(path, O_RDONLY);
    if (fd < 0) return 0;
    struct stat st;
    if (fstat(fd, &st) != 0) { close(fd); return 0; }
    if (st.st_size == 0) { close(fd); return -1; }
    void* addr = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_SHARED,
                      fd, 0);
    close(fd);
    if (addr == MAP_FAILED) return 0;
    std::lock_guard<std::mutex> g(g_buffers_mu);
    int64_t h = g_next_handle++;
    g_buffers[h] = MappedBuffer{addr, (int64_t)st.st_size, 1};
    return h;
}

const void* pn_mmap_addr(int64_t handle) {
    std::lock_guard<std::mutex> g(g_buffers_mu);
    auto it = g_buffers.find(handle);
    return it == g_buffers.end() ? nullptr : it->second.addr;
}

int64_t pn_mmap_size(int64_t handle) {
    std::lock_guard<std::mutex> g(g_buffers_mu);
    auto it = g_buffers.find(handle);
    return it == g_buffers.end() ? -1 : it->second.size;
}

int32_t pn_mmap_acquire(int64_t handle) {
    std::lock_guard<std::mutex> g(g_buffers_mu);
    auto it = g_buffers.find(handle);
    if (it == g_buffers.end() || it->second.refcount <= 0) return 0;
    it->second.refcount++;
    return 1;
}

// returns remaining refcount; unmaps at zero
int32_t pn_mmap_release(int64_t handle) {
    std::lock_guard<std::mutex> g(g_buffers_mu);
    auto it = g_buffers.find(handle);
    if (it == g_buffers.end()) return -1;
    int32_t rc = --it->second.refcount;
    if (rc == 0) {
        munmap(it->second.addr, (size_t)it->second.size);
        g_buffers.erase(it);
    }
    return rc;
}

int64_t pn_mmap_open_count() {
    std::lock_guard<std::mutex> g(g_buffers_mu);
    return (int64_t)g_buffers.size();
}

// ---------------------------------------------------------------------------
// CRC32 (zlib polynomial, table-driven)
// ---------------------------------------------------------------------------

static uint32_t g_crc_table[256];
static bool g_crc_init = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        g_crc_table[i] = c;
    }
    g_crc_init = true;
}

uint32_t pn_crc32(const uint8_t* data, int64_t len, uint32_t crc) {
    if (!g_crc_init) crc_init();
    crc = ~crc;
    for (int64_t i = 0; i < len; i++)
        crc = g_crc_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

// CRC over a whole file without loading it into Python
int64_t pn_crc32_file(const char* path, uint32_t seed) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    uint8_t buf[1 << 16];
    uint32_t crc = seed;
    size_t got;
    while ((got = fread(buf, 1, sizeof(buf), f)) > 0)
        crc = pn_crc32(buf, (int64_t)got, crc);
    fclose(f);
    return (int64_t)crc;
}

// ---------------------------------------------------------------------------
// delta + varint posting lists (sorted doc-id compression, the storage form
// of the inverted index; ref: RoaringBitmap container compression role)
// ---------------------------------------------------------------------------

// encode sorted int32 doc ids; returns bytes written or -1 if dst too small
int64_t pn_varint_encode(const int32_t* src, int64_t n, uint8_t* dst,
                         int64_t dst_cap) {
    int64_t o = 0;
    int32_t prev = 0;
    for (int64_t i = 0; i < n; i++) {
        uint32_t d = (uint32_t)(src[i] - prev);
        prev = src[i];
        while (d >= 0x80) {
            if (o >= dst_cap) return -1;
            dst[o++] = (uint8_t)(d | 0x80);
            d >>= 7;
        }
        if (o >= dst_cap) return -1;
        dst[o++] = (uint8_t)d;
    }
    return o;
}

// encode `num_lists` posting lists in one pass: docs[offsets[i]..offsets[i+1])
// is list i (sorted); delta base resets per list. byte_offsets[num_lists+1]
// receives the per-list byte ranges. Returns total bytes or -1 on overflow.
int64_t pn_varint_encode_lists(const int32_t* docs, const int64_t* offsets,
                               int64_t num_lists, uint8_t* dst,
                               int64_t dst_cap, int64_t* byte_offsets) {
    int64_t o = 0;
    byte_offsets[0] = 0;
    for (int64_t l = 0; l < num_lists; l++) {
        int32_t prev = 0;
        for (int64_t i = offsets[l]; i < offsets[l + 1]; i++) {
            uint32_t d = (uint32_t)(docs[i] - prev);
            prev = docs[i];
            while (d >= 0x80) {
                if (o >= dst_cap) return -1;
                dst[o++] = (uint8_t)(d | 0x80);
                d >>= 7;
            }
            if (o >= dst_cap) return -1;
            dst[o++] = (uint8_t)d;
        }
        byte_offsets[l + 1] = o;
    }
    return o;
}

int64_t pn_varint_decode(const uint8_t* src, int64_t len, int32_t* dst,
                         int64_t n) {
    int64_t o = 0;
    int32_t prev = 0;
    for (int64_t i = 0; i < n; i++) {
        uint32_t d = 0;
        int shift = 0;
        while (true) {
            if (o >= len) return -1;
            uint8_t b = src[o++];
            d |= (uint32_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        prev += (int32_t)d;
        dst[i] = prev;
    }
    return n;
}

}  // extern "C"
